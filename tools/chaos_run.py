"""Reproducible chaos-suite entry point.

Run: python tools/chaos_run.py --seed N
        [--faults kill,torn,lease,net,client,split,merge,disk]
        [--docs D] [--clients C] [--ops K] [--timeout S] [--keep DIR]
        [--deli scalar|kernel] [--log-format json|columnar]
        [--boxcar-rate R] [--metrics-out PATH] [--trace-wire]
        [--partitions N] [--workers W] [--devices N] [--elastic]
        [--device-plane DxM] [--fold-backend kernel|overlay]
        [--summarizer] [--summary-ops N] [--retention] [--fused-hop]
        [--ingress [--bad-submits N] [--ingress-rate R]
         [--ingress-backlog B]] [--autoscale]
        [--downstream fused|split] [--scenario hotdoc]

`--device-plane DxM` (with `--deli kernel`) runs the farm on ONE 2-D
``docs x model`` device mesh (`parallel.device_plane`): the kernel
deli children shard their doc-slot pools on the plane's docs-axis
slice while the summarizer's merge-tree folds lay out over the whole
pool, all under docs*model forced virtual host devices (the CPU-CI
emulation of a real slice). `--fold-backend overlay` (with
`--summarizer`) additionally folds summaries through the
overlay-pallas engine in INTERPRETER mode (`FLUID_FOLD_INTERPRET=1` —
the CPU-CI correctness form), so the summary-integrity gate proves
the overlay backend's content-addressed blobs bit-identical to the
kernel fold's and to cold scalar replay, under kill faults.

`--scenario hotdoc` reshapes the workload with a traffic-profile
scenario (`testing.chaos.SCENARIO_PROFILES`): a contiguous viral-doc
storm block — a swarm of extra writers piling onto one document — is
woven into the middle of the stream, and the seeded kill/split points
are clamped INSIDE the storm window, so the faults land while the
storm is in flight. Convergence must still be bit-identical with zero
dup/skip. (`testing/scenarios.py` holds the open-loop, latency-
measured scenario benches; this flag is their fault-injection twin.)

`--ingress` (with `--partitions` > 1) puts the supervised admission
front door (`server.ingress.IngressRole`) in front of the fabric: the
workload feeds the `ingress` topic with signed tenant tokens, the
front door joins the kill schedule, `--bad-submits` seeded invalid
records (tampered token / oversized / unknown tenant) must each be
nacked exactly once and NEVER sequenced, and throttle-nacked valid
submits are retried to convergence. `--ingress-rate` /
`--ingress-backlog` stage an overload episode (per-tenant token
bucket / per-partition backlog budget) whose throttle nacks and
bounded backlog ride the verdict.

`--autoscale` (elastic) closes the scaling loop: the fabric
supervisor's `AutoscalePolicy` watches per-partition throughput and
stages splits/merges itself — convergence then also requires a
POLICY-driven epoch change to have fired mid-stream.

`--downstream fused|split` promotes scriptorium/broadcaster/scribe to
per-partition supervised consumers inside the workers; convergence
then also requires the merged durable AND broadcast legs bit-identical
to the golden with zero dup/skip.

`--fused-hop` collapses the scriptorium+broadcaster pair into the ONE
fused durable+broadcast consumer
(`supervisor.ScriptoriumBroadcasterRole`): kill faults then target the
fused role, and convergence (the same durable+broadcast topic reads)
proves the fused hop — durable leg fsynced, broadcast leg unfsynced —
bit-identical to the split pair with zero dup/skip under the same
faults. Classic single-partition farm only.

`--summarizer` runs the summary service (`server.summarizer`) as a
fifth supervised role, includes it in the kill schedule, and extends
the convergence verdict with SUMMARY INTEGRITY: the deterministic
manifest count reached with no (doc, seq) fork or duplicate —
restarts re-emit byte-identical content-addressed summaries — and the
newest summary + op tail booting bit-identical to a cold full-log
replay. Classic single-partition farm only (`--summary-ops` sets the
cadence).

`--retention` (implies `--summarizer` and the columnar log format)
runs the retention plane (`server.retention.RetentionRole`) as a
SIXTH supervised role: summary-driven fenced TRUNCATE of the
deltas/rawdeltas op logs plus mark-and-sweep castore GC. The role
joins the kill schedule AND two SEEDED kill points fire mid-run —
between the fenced truncate commit record and the physical reclaim,
and mid-GC-sweep — so the verdict proves recovery ROLLS each
committed cut forward with zero dup/skip while summary + tail still
boots bit-identical to a cold replay off the untruncated durable leg.

`--trace-wire` stamps per-stage wall-clock timestamps onto the farm's
wire records (side "tr" key — digests compare canonical records, so
convergence is unaffected) and attaches the slow-op flight recorder's
spans to the report and the `--metrics-out` line: a chaos run that
regresses tail latency names the exact slowest ops it produced. On
the SHARDED runner (`--partitions` > 1) combine it with
`--downstream fused|split`: the per-partition broadcaster stages feed
each worker's flight recorder and the spans come back PARTITION-
TAGGED through the worker heartbeats (the fabric-wide /traces
surface). Without a downstream stage the fabric has no broadcast hop,
so tracing yields submit→stamp quantiles but no e2e spans.

`--faults split,merge,disk` (with `--partitions` > 1) runs the ELASTIC
hash-range fabric and injects topology changes as faults: a live
range SPLIT mid-run (the pre-split owner's stale-fence write must be
demonstrably FencedError-rejected), a live MERGE of adjacent ranges,
and a DISK episode (ENOSPC + stalled fsync on the workers' durable
writes — roles must degrade gracefully through bounded-retry backoff,
`degraded` visible in health(), and recover with no lost acknowledged
record). `--elastic` alone runs the classic fault set against the
elastic fabric.

`--devices N` (with `--deli kernel`) shards the kernel deli's doc-slot
pool across an N-device mesh inside the deli child (forced virtual
host CPU devices — the CPU-CI emulation of an N-chip slice). The
golden digest still folds single-device in-proc, so a converging run
proves the SHARDED sequencer carries the bit-identical stream under
the same faults.

`--partitions N` (>1) runs the run against the SHARDED ordering fabric
(server.shard_fabric): `--workers W` lease-balanced shard workers over
N partition topic pairs; faults then target workers (kill) and
partition leases (lease), and convergence compares the merged
sequenced stream across every deltas-p{k} with the single-partition
in-proc golden. The "net" fault class is single-partition only (the
fabric runner has no socket consumer to dup/delay) — it drops out of
the default fault set with --partitions >1 and is rejected loudly if
named explicitly.

`--log-format columnar` runs every farm topic as a binary record-batch
log (server.columnar_log) instead of JSONL; the golden digest still
folds in-process, so convergence proves the columnar op-log carries
the identical stream under faults. `--boxcar-rate R` makes a fraction
of the ingress stream ride wire boxcar records (atomic multi-op
ingress, the ROADMAP (d) schema rev).

`--deli kernel` runs the farm with the batched TPU sequencer
(server.deli_kernel.KernelDeliRole) in place of the scalar deli; the
golden digest still comes from the scalar production path, so
convergence proves the batched pipeline exactly-once under faults.

Builds the seeded workload, computes the no-fault GOLDEN digest with
the production deli/scribe code in-process, launches the supervised
multi-process lambda farm (`server.supervisor.ServiceSupervisor`),
injects the selected fault classes at seeded points, and reports
whether the farm converged bit-identical to golden with zero duplicate
and zero skipped sequence numbers. Exit code 0 iff converged — the CI
gate form of tests/test_chaos_recovery.py.

`--keep DIR` runs in DIR and leaves the topics/checkpoints/lease files
(plus `metrics.jsonl` role snapshots) behind for post-mortem (default:
a throwaway temp dir).

Observability: the report includes the fault/recovery TIMELINE
(timestamped chaos faults + supervisor restarts) and a metrics table
(role pump sizes, checkpoint writes/bytes/durations, fence rejections)
merged from every role's final heartbeat snapshot. `--metrics-out
PATH` appends the merged snapshot as one JSONL line for
tools/metrics_report.py.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.server.supervisor import (  # noqa: E402
    DELI_IMPLS,
    LOG_FORMATS,
)
from fluidframework_tpu.testing.chaos import (  # noqa: E402
    ALL_FAULT_CLASSES,
    ELASTIC_FAULTS,
    FAULT_CLASSES,
    SCENARIO_PROFILES,
    ChaosConfig,
    run_chaos,
)


def main() -> int:
    args = list(sys.argv[1:])

    def _take(flag: str, default):
        if flag in args:
            i = args.index(flag)
            val = args[i + 1]
            del args[i:i + 2]
            return val
        return default

    seed = int(_take("--seed", "0"))
    metrics_out = _take("--metrics-out", None)
    faults_arg = _take("--faults", None)
    n_partitions = int(_take("--partitions", "1"))
    elastic = "--elastic" in args
    if elastic:
        args.remove("--elastic")
    trace_wire = "--trace-wire" in args
    if trace_wire:
        args.remove("--trace-wire")
    summarizer = "--summarizer" in args
    if summarizer:
        args.remove("--summarizer")
    retention = "--retention" in args
    if retention:
        # The retention plane rides the summary service and the
        # columnar log by construction: --retention implies both
        # (an explicit --log-format json still errors loudly in
        # ChaosConfig validation).
        args.remove("--retention")
        summarizer = True
    fused_hop = "--fused-hop" in args
    if fused_hop:
        args.remove("--fused-hop")
    ingress = "--ingress" in args
    if ingress:
        args.remove("--ingress")
    autoscale = "--autoscale" in args
    if autoscale:
        args.remove("--autoscale")
    downstream = _take("--downstream", None)
    scenario = _take("--scenario", None)
    bad_submits = int(_take("--bad-submits", "6"))
    ingress_rate = float(_take("--ingress-rate", "0"))
    ingress_backlog = int(_take("--ingress-backlog", "0"))
    summary_ops = int(_take("--summary-ops", "32"))
    if faults_arg is None:
        # Default fault set: the classic classes the chosen runner
        # supports. The sharded runner has no socket consumer, so
        # "net" is only meaningful (and only accepted)
        # single-partition; the elastic classes (split/merge/disk)
        # are opt-in — naming them turns the elastic fabric on.
        default_faults = [f for f in FAULT_CLASSES
                          if n_partitions == 1 or f != "net"]
        faults_arg = ",".join(default_faults)
    faults = tuple(f for f in faults_arg.split(",") if f)
    cfg = ChaosConfig(
        seed=seed,
        faults=faults,
        n_docs=int(_take("--docs", "2")),
        n_clients=int(_take("--clients", "3")),
        ops_per_client=int(_take("--ops", "40")),
        timeout_s=float(_take("--timeout", "120")),
        shared_dir=_take("--keep", None),
        deli_impl=_take("--deli", "scalar"),
        log_format=_take("--log-format",
                         "columnar" if retention else "json"),
        boxcar_rate=float(_take("--boxcar-rate", "0")),
        retention=retention,
        n_partitions=n_partitions,
        n_workers=int(_take("--workers", "2")),
        deli_devices=(lambda v: int(v) if v else None)(
            _take("--devices", None)
        ),
        device_plane=_take("--device-plane", None),
        fold_backend=_take("--fold-backend", None),
        elastic=elastic,
        trace_wire=trace_wire,
        summarizer=summarizer,
        summary_ops=summary_ops,
        fused_hop=fused_hop,
        ingress=ingress,
        bad_submits=bad_submits,
        ingress_rate=ingress_rate,
        ingress_backlog=ingress_backlog,
        autoscale=autoscale,
        downstream=downstream,
        scenario=scenario,
    )
    unknown = set(faults) - set(ALL_FAULT_CLASSES)
    if (unknown or args or cfg.deli_impl not in DELI_IMPLS
            or cfg.log_format not in LOG_FORMATS
            or (downstream is not None
                and downstream not in ("fused", "split"))
            or (scenario is not None
                and scenario not in SCENARIO_PROFILES)):
        print(
            f"unknown faults {sorted(unknown)} / leftover args {args}; "
            f"faults are chosen from {','.join(ALL_FAULT_CLASSES)} "
            f"({','.join(ELASTIC_FAULTS)} need --partitions > 1); "
            f"--deli is one of {'|'.join(DELI_IMPLS)}; "
            f"--log-format is one of {'|'.join(LOG_FORMATS)}; "
            f"--scenario is one of {'|'.join(SCENARIO_PROFILES)}",
            file=sys.stderr,
        )
        return 2
    shard = (f" partitions={cfg.n_partitions} workers={cfg.n_workers}"
             + (" elastic" if cfg.elastic
                or any(f in ELASTIC_FAULTS for f in faults) else "")
             if cfg.n_partitions > 1 else "")
    dev = (f" devices={cfg.deli_devices}"
           if cfg.deli_devices and cfg.deli_devices > 1 else "")
    dev += (f" plane={cfg.device_plane}" if cfg.device_plane else "")
    dev += (f" fold={cfg.fold_backend}" if cfg.fold_backend else "")
    print(f"chaos run: seed={seed} faults={','.join(faults)} "
          f"docs={cfg.n_docs} clients={cfg.n_clients} "
          f"ops/client={cfg.ops_per_client} deli={cfg.deli_impl} "
          f"log={cfg.log_format} boxcar_rate={cfg.boxcar_rate}"
          f"{shard}{dev}{' fused-hop' if cfg.fused_hop else ''}"
          f"{f' scenario={cfg.scenario}' if cfg.scenario else ''}",
          flush=True)
    res = run_chaos(cfg)
    print(f"golden digest : {res.golden_digest}")
    print(f"farm digest   : {res.digest}")
    if res.client_digest is not None:
        print(f"client digest : {res.client_digest}  (flaky delivery edge)")
    print(f"scribe fold   : {'match' if res.scribe_ok else 'MISMATCH'}")
    print(f"dup seqs={res.duplicate_seqs} skipped seqs={res.skipped_seqs} "
          f"fence rejections={res.fence_rejections}")
    if summarizer:
        print(f"summaries     : {res.summary_manifests} manifests, "
              f"integrity {'OK' if res.summaries_ok else 'VIOLATED'} "
              f"(no fork/dup; summary+tail == cold replay)")
    if retention:
        print(f"retention     : {res.truncations} truncation(s) "
              f"committed, deltas base {res.retention_base_records}, "
              f"gc deleted {res.gc_deleted}, integrity "
              f"{'OK' if res.retention_ok else 'VIOLATED'} "
              f"(commit-then-reclaim rolled forward; kill points "
              f"fired)")
    if ingress:
        print(f"front door    : nacks={res.ingress_nacks} "
              f"bad-never-sequenced="
              f"{'OK' if res.never_sequenced_ok else 'VIOLATED'} "
              f"throttle_retries={res.throttle_retries}")
    if autoscale:
        print(f"autoscale     : {res.autoscale_actions} policy "
              f"action(s) staged")
    if downstream:
        print(f"downstream    : durable+broadcast legs "
              f"{'match' if res.downstream_ok else 'MISMATCH'}")
    if res.epochs:
        print(f"topology epochs: {res.epochs}")
    if "disk" in faults:
        print(f"degraded seen : {res.degraded_seen}")
    print(f"restarts: {res.restarts}")
    if res.timeline:
        t0 = res.timeline[0][0]
        print("fault/recovery timeline:")
        for ts, ev in res.timeline:
            print(f"  +{ts - t0:7.3f}s  {ev}")
    else:
        for e in res.events:
            print(f"  {e}")
    if res.metrics:
        from fluidframework_tpu.utils.metrics import (
            dump_snapshot_line,
            format_report,
        )

        print("farm metrics (merged from role heartbeats):")
        for line in format_report([res.metrics]).splitlines():
            print(f"  {line}")
        if res.slow_ops:
            from metrics_report import slow_ops_report

            print(slow_ops_report([{"slow_ops": res.slow_ops}], top=5))
        if metrics_out:
            dump_snapshot_line(
                metrics_out, res.metrics, source="chaos_run", seed=seed,
                faults=",".join(faults), deli=cfg.deli_impl,
                log_format=cfg.log_format,
                # The exact slow ops ride the same artifact line, so a
                # tail regression caught by the snapshot's quantiles
                # comes with its evidence attached.
                slow_ops=res.slow_ops,
            )
            print(f"metrics snapshot appended to {metrics_out}")
    print("CONVERGED" if res.converged else f"DIVERGED ({res.detail})")
    return 0 if res.converged else 1


if __name__ == "__main__":
    raise SystemExit(main())
