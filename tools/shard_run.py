"""Sharded ordering fabric runner: the multi-partition kernel-deli
farm end to end, from one command.

Run: python tools/shard_run.py [--partitions N] [--workers W]
        [--docs D] [--clients C] [--ops K] [--deli scalar|kernel]
        [--log-format json|columnar] [--boxcar-rate R] [--ttl S]
        [--timeout S] [--keep DIR] [--kill-worker I]
        [--elastic] [--split-mid-run] [--merge-after-split]
        [--autoscale] [--downstream fused|split]

`--elastic` runs the hash-range topology (`queue.RangeLeaseStore`):
partitions are range leases, routed by ``(epoch, hash(doc))``, and
the merged read rides per-range cursors across the whole topology
history. `--split-mid-run` stages a live split of the widest owned
range once half the workload is fed (`--merge-after-split` merges the
children back before the drain completes) — a live demonstration
that capacity follows load without a restart: the order must not
notice N changing mid-stream.

`--autoscale` (implies elastic) hands the split decision to the
supervisor's `AutoscalePolicy` instead: the feed is paced, the policy
watches per-partition throughput off the worker heartbeats, and a
LOAD-driven split must commit before the run ends — the closed
autoscaling loop, live. `--downstream fused|split` runs per-partition
scriptorium/broadcaster/scribe consumers inside the workers and
verifies the merged durable leg against the golden too.

Builds a seeded workload over partition-balanced doc names, starts
`server.shard_fabric.ShardFabricSupervisor` (W supervised shard
workers lease-balancing N partitions), routes the stream through
`ShardRouter`, waits for the merged ``deltas-p{k}`` streams to drain,
and reports aggregate throughput, final partition ownership, worker
restarts, and a convergence verdict against the in-proc
single-partition golden (exit 0 iff bit-identical with zero
duplicate/skipped seqs).

`--kill-worker I` SIGKILLs worker slot I once mid-stream — a live
demonstration of fenced partition handoff (the supervisor restarts
it; its partitions rebalance; the order must not notice).

`--keep DIR` runs in DIR and leaves topics/leases/checkpoints/worker
heartbeats behind for inspection (default: throwaway temp dir).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.server.shard_fabric import (  # noqa: E402
    AutoscalePolicy,
    ShardFabricSupervisor,
    ShardRouter,
    spread_doc_names,
)
from fluidframework_tpu.server.supervisor import (  # noqa: E402
    DELI_IMPLS,
    LOG_FORMATS,
)
from fluidframework_tpu.testing.chaos import (  # noqa: E402
    ChaosConfig,
    build_workload,
    golden_stream,
    sequence_integrity,
    stream_digest,
)


def main() -> int:
    args = list(sys.argv[1:])

    def _take(flag: str, default):
        if flag in args:
            i = args.index(flag)
            val = args[i + 1]
            del args[i:i + 2]
            return val
        return default

    n_partitions = int(_take("--partitions", "4"))
    n_workers = int(_take("--workers", "2"))
    cfg = ChaosConfig(
        seed=int(_take("--seed", "0")),
        faults=(),
        n_docs=int(_take("--docs", "8")),
        n_clients=int(_take("--clients", "3")),
        ops_per_client=int(_take("--ops", "40")),
        boxcar_rate=float(_take("--boxcar-rate", "0")),
        n_partitions=n_partitions,
    )
    deli = _take("--deli", "scalar")
    log_format = _take("--log-format", "json")
    ttl = float(_take("--ttl", "0.75"))
    timeout = float(_take("--timeout", "120"))
    keep = _take("--keep", None)
    kill_worker = _take("--kill-worker", None)
    elastic = "--elastic" in args
    if elastic:
        args.remove("--elastic")
    split_mid_run = "--split-mid-run" in args
    if split_mid_run:
        args.remove("--split-mid-run")
        elastic = True
    merge_after = "--merge-after-split" in args
    if merge_after:
        args.remove("--merge-after-split")
    autoscale = "--autoscale" in args
    if autoscale:
        args.remove("--autoscale")
        elastic = True
    downstream = _take("--downstream", None)
    if merge_after and not split_mid_run:
        print("--merge-after-split needs --split-mid-run",
              file=sys.stderr)
        return 2
    if autoscale and split_mid_run:
        print("--autoscale replaces --split-mid-run (the policy "
              "stages the split)", file=sys.stderr)
        return 2
    if (args or deli not in DELI_IMPLS
            or log_format not in LOG_FORMATS
            or (downstream is not None
                and downstream not in ("fused", "split"))):
        print(
            f"leftover args {args}; --deli is one of "
            f"{'|'.join(DELI_IMPLS)}; --log-format is one of "
            f"{'|'.join(LOG_FORMATS)}",
            file=sys.stderr,
        )
        return 2

    shared = keep or tempfile.mkdtemp(prefix="shard-run-")
    workload = build_workload(cfg)
    golden = golden_stream(workload, os.path.join(shared, "golden"))
    gdigest = stream_digest(golden)
    print(
        f"shard run: partitions={n_partitions} workers={n_workers} "
        f"deli={deli} log={log_format} docs={cfg.n_docs} "
        f"records={len(workload)} dir={shared}", flush=True,
    )
    assert set(spread_doc_names(cfg.n_docs, n_partitions)) == {
        r["doc"] for r in workload if isinstance(r, dict) and "doc" in r
    }

    router = ShardRouter(shared, n_partitions, log_format,
                         elastic=elastic)
    policy = AutoscalePolicy(
        split_rate=5.0, merge_rate=0.01, sustain_s=max(0.5, ttl),
        min_interval_s=max(2.0, 4 * ttl),
        max_ranges=n_partitions + 2,
    ) if autoscale else None
    sup = ShardFabricSupervisor(
        shared, n_workers=n_workers, n_partitions=n_partitions,
        ttl_s=ttl, deli_impl=deli, log_format=log_format,
        elastic=elastic, downstream=downstream, autoscale=policy,
    ).start()
    killed = False
    split_cmd = None
    merge_cmd = None
    t0 = time.time()
    try:
        fed = 0
        deadline = time.time() + timeout
        ops = []
        reader = router.merged_reader()
        dur_reader = (router.merged_reader("durable")
                      if downstream else None)
        dur_ops = []
        # The autoscale demo paces the feed (~2 batches per TTL): the
        # policy needs rate samples + its sustain window, and the
        # point is the split landing MID-stream.
        feed_gap = ttl / 2 if autoscale else 0.0
        last_feed = 0.0
        while time.time() < deadline:
            sup.poll_once()
            if fed < len(workload) and (
                    not feed_gap
                    or time.time() - last_feed >= feed_gap):
                last_feed = time.time()
                router.append(workload[fed:fed + 64])
                fed += 64
                if (kill_worker is not None and not killed
                        and fed >= len(workload) // 2):
                    slot = f"shard-w{int(kill_worker)}"
                    proc = sup.procs.get(slot)
                    if proc is not None and proc.poll() is None:
                        proc.kill()
                        killed = True
                        print(f"SIGKILL {slot} mid-stream", flush=True)
                if (split_mid_run and split_cmd is None
                        and fed >= len(workload) // 2):
                    split_cmd = sup.request_split()
                    print("split requested mid-stream", flush=True)
            if (split_cmd is not None and merge_after
                    and merge_cmd is None):
                done = sup.control_result(split_cmd)
                topo = sup.topology()
                if done and not done.get("error") and topo:
                    ranges = sorted(topo["ranges"],
                                    key=lambda e: e["lo"])
                    for a, b in zip(ranges, ranges[1:]):
                        if a["preds"] and a["preds"] == b["preds"]:
                            merge_cmd = sup.request_merge(
                                a["rid"], b["rid"]
                            )
                            print("merge requested mid-stream",
                                  flush=True)
                            break
            # Merged catch-up read: per-range cursors across the whole
            # topology history — records written under epoch E stay
            # readable after E+1, incrementally.
            ops += [r for r in reader.poll()
                    if isinstance(r, dict) and r.get("kind") == "op"]
            if dur_reader is not None:
                dur_ops += [
                    r for r in dur_reader.poll()
                    if isinstance(r, dict) and r.get("kind") == "op"
                ]
            # A requested topology change must actually COMMIT before
            # the run ends — a small workload must not outrun the demo.
            ctl_done = (
                (split_cmd is None
                 or sup.control_result(split_cmd) is not None)
                and (not merge_after or split_cmd is None
                     or (merge_cmd is not None
                         and sup.control_result(merge_cmd) is not None))
            )
            topo_now = sup.topology() if autoscale else None
            if (fed >= len(workload) and len(ops) >= len(golden)
                    and ctl_done
                    # The LOAD-driven split must have committed.
                    and (not autoscale or (topo_now or {}).get(
                        "epoch", 1) > 1)
                    and (dur_reader is None
                         or len(dur_ops) >= len(golden))):
                break
            time.sleep(0.02)
        elapsed = time.time() - t0
    finally:
        sup.stop()

    digest = stream_digest(ops)
    dups, skips = sequence_integrity(ops)
    converged = digest == gdigest and dups == 0 and skips == 0
    if downstream:
        ddigest = stream_digest(dur_ops)
        ddups, dskips = sequence_integrity(dur_ops)
        converged = converged and ddigest == gdigest \
            and ddups == 0 and dskips == 0
        print(f"durable digest: {ddigest} "
              f"({len(dur_ops)} ops, dups={ddups} skips={dskips})")
    if autoscale:
        converged = converged and len(sup.autoscale.actions) > 0
        print(f"autoscale     : {len(sup.autoscale.actions)} policy "
              f"action(s): {sup.autoscale.actions}")
    topo = sup.topology()
    print(f"golden digest : {gdigest}")
    print(f"fabric digest : {digest}")
    print(f"ops           : {len(ops)}/{len(golden)} in {elapsed:.2f}s "
          f"({len(ops) / max(elapsed, 1e-9):,.0f} ops/s aggregate)")
    print(f"dup seqs={dups} skipped seqs={skips}")
    print(f"partition owners: {sup.partition_owners()}")
    if topo is not None:
        print(f"topology epoch {topo['epoch']}: "
              f"{[e['rid'] for e in topo['ranges']]}")
    print(f"worker restarts : {sup.restarts}")
    print(json.dumps({
        "metric": "shard_run", "partitions": n_partitions,
        "workers": n_workers, "deli": deli, "log_format": log_format,
        "elastic": elastic,
        "epoch": topo["epoch"] if topo else None,
        "records": len(workload), "ops": len(ops),
        "seconds": round(elapsed, 3), "converged": converged,
        "restarts": sup.restarts,
        "autoscale_actions": (len(sup.autoscale.actions)
                              if autoscale else 0),
        "downstream": downstream,
    }))
    print("CONVERGED" if converged else "DIVERGED")
    if keep is None and converged:
        import shutil

        shutil.rmtree(shared, ignore_errors=True)
    return 0 if converged else 1


if __name__ == "__main__":
    raise SystemExit(main())
