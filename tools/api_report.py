"""Generate the public-API surface reports (the api-report role).

The reference checks in `api-report/*.api.md` per package
(api-extractor output) as the public-API regression contract: any
surface change shows up as a diff a reviewer must approve. This tool
walks each package's public surface (module `__all__` when present,
else underscore filtering) and renders classes/functions with their
signatures into `api_report/<package>.api.txt`, deterministically.

tests/test_api_report.py regenerates the reports in-memory and fails
on any drift, naming this tool — the same accept-the-diff workflow.

Usage: python tools/api_report.py [--check]
"""

from __future__ import annotations

import importlib
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PACKAGES = [
    "fluidframework_tpu.core.mergetree",
    "fluidframework_tpu.core.native_engine",
    "fluidframework_tpu.core.overlay_fold",
    "fluidframework_tpu.core.overlay_replay",
    "fluidframework_tpu.core.columnar_replay",
    "fluidframework_tpu.ops.mergetree_kernel",
    "fluidframework_tpu.ops.overlay_pallas",
    "fluidframework_tpu.ops.overlay_ref",
    "fluidframework_tpu.ops.sequencer_kernel",
    "fluidframework_tpu.dds",
    "fluidframework_tpu.dds.sequence",
    "fluidframework_tpu.dds.map",
    "fluidframework_tpu.dds.matrix",
    "fluidframework_tpu.tree",
    "fluidframework_tpu.runtime",
    "fluidframework_tpu.runtime.container_runtime",
    "fluidframework_tpu.runtime.datastore",
    "fluidframework_tpu.loader",
    "fluidframework_tpu.drivers",
    "fluidframework_tpu.server",
    "fluidframework_tpu.server.columnar_log",
    "fluidframework_tpu.server.deli_kernel",
    "fluidframework_tpu.server.ingress",
    "fluidframework_tpu.server.monitor",
    "fluidframework_tpu.server.queue",
    "fluidframework_tpu.server.retention",
    "fluidframework_tpu.server.riddler",
    "fluidframework_tpu.server.shard_fabric",
    "fluidframework_tpu.server.summarizer",
    "fluidframework_tpu.server.supervisor",
    "fluidframework_tpu.framework",
    "fluidframework_tpu.parallel",
    "fluidframework_tpu.parallel.device_plane",
    "fluidframework_tpu.protocol",
    "fluidframework_tpu.protocol.record_batch",
    "fluidframework_tpu.testing",
    "fluidframework_tpu.utils",
    "fluidframework_tpu.utils.devices",
    "fluidframework_tpu.utils.metrics",
]

REPORT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "api_report",
)


def _sig(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def _public_names(mod) -> list:
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in vars(mod) if not n.startswith("_")]
        # Without __all__, skip re-exported modules and foreign names.
        names = [
            n for n in names
            if getattr(getattr(mod, n), "__module__", mod.__name__)
            == mod.__name__
            and not inspect.ismodule(getattr(mod, n))
        ]
    return sorted(names)


def render(module_name: str) -> str:
    mod = importlib.import_module(module_name)
    lines = [f"## API report: {module_name}", ""]
    for name in _public_names(mod):
        obj = getattr(mod, name)
        if inspect.isclass(obj):
            bases = ", ".join(
                b.__name__ for b in obj.__bases__ if b is not object
            )
            lines.append(f"class {name}({bases})" if bases else f"class {name}")
            members = []
            for mname, m in sorted(vars(obj).items()):
                if mname.startswith("_") and mname != "__init__":
                    continue
                if inspect.isfunction(m):
                    members.append(f"    def {mname}{_sig(m)}")
                elif isinstance(m, property):
                    members.append(f"    property {mname}")
                elif isinstance(m, (classmethod, staticmethod)):
                    members.append(
                        f"    def {mname}{_sig(m.__func__)}  # {type(m).__name__}"
                    )
            lines.extend(members)
        elif inspect.isfunction(obj):
            lines.append(f"def {name}{_sig(obj)}")
        elif not inspect.ismodule(obj):
            lines.append(f"{name} = {type(obj).__name__}")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def main() -> None:
    check = "--check" in sys.argv
    drift = []
    os.makedirs(REPORT_DIR, exist_ok=True)
    for pkg in PACKAGES:
        text = render(pkg)
        path = os.path.join(REPORT_DIR, pkg + ".api.txt")
        if check:
            old = open(path).read() if os.path.exists(path) else None
            if old != text:
                drift.append(pkg)
        else:
            with open(path, "w") as f:
                f.write(text)
    expected = {pkg + ".api.txt" for pkg in PACKAGES}
    orphans = sorted(
        f for f in os.listdir(REPORT_DIR)
        if f.endswith(".api.txt") and f not in expected
    )
    if check:
        if orphans:
            drift.extend(f"orphan:{f}" for f in orphans)
        if drift:
            print("API drift in:", ", ".join(drift))
            sys.exit(1)
        print(f"{len(PACKAGES)} API reports clean")
    else:
        for f in orphans:
            os.remove(os.path.join(REPORT_DIR, f))
        print(f"wrote {len(PACKAGES)} reports to {REPORT_DIR}"
              + (f"; removed {len(orphans)} orphans" if orphans else ""))


if __name__ == "__main__":
    main()
