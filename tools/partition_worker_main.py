"""Partitioned sequencer worker: one node of a multi-node ordering
service.

Run: python tools/partition_worker_main.py <shared_dir> <worker_id>
        <n_partitions> [--ttl SECONDS] [--max-partitions K]

Workers coordinate ONLY through the shared directory (the role Kafka +
ZooKeeper play for routerlicious pods): each sweeps the partition
leases (`server.queue.LeaseManager`), sequences submissions for the
documents of every partition it owns (`server.sequencer
.DocumentSequencer`, the deli role), appends the stamped messages to
the partition's shared `sequenced` topic, and checkpoints
(consumer offset + sequencer state, fenced against deposed owners)
after every batch. Kill a worker mid-stream and a peer's next sweep
takes its expired leases over, restores the checkpoint, and resumes
exactly where the dead worker stopped — no message lost or
double-sequenced (tests/test_partition_leases.py).

Prints "READY <worker_id>" once leases are first swept.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.server.queue import (  # noqa: E402
    LeaseManager,
    SharedFileConsumer,
    SharedFileTopic,
)
from fluidframework_tpu.protocol.messages import (  # noqa: E402
    DocumentMessage,
    NackMessage,
)
from fluidframework_tpu.server.sequencer import DocumentSequencer  # noqa: E402


class PartitionWorker:
    def __init__(self, shared_dir: str, worker_id: str,
                 n_partitions: int, ttl_s: float = 2.0,
                 max_partitions: int | None = None):
        self.dir = shared_dir
        self.worker_id = worker_id
        self.n_partitions = n_partitions
        self.max_partitions = max_partitions
        self.leases = LeaseManager(
            os.path.join(shared_dir, "leases"), worker_id, ttl_s
        )
        # partition -> (fence, consumer, {doc: DocumentSequencer})
        self.owned: dict = {}

    # ----------------------------------------------------- checkpoints

    def _ckpt_path(self, p: int) -> str:
        return os.path.join(self.dir, f"ckpt-p{p}.json")

    def _load_checkpoint(self, p: int) -> dict:
        try:
            with open(self._ckpt_path(p)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"offset": 0, "sequencers": {}, "fence": 0}

    def _save_checkpoint(self, p: int, fence: int, offset: int,
                         sequencers: dict) -> None:
        cur = self._load_checkpoint(p)
        if int(cur.get("fence", 0)) > fence:
            raise RuntimeError("deposed: newer fence checkpointed")
        tmp = self._ckpt_path(p) + f".tmp.{self.worker_id}"
        with open(tmp, "w") as f:
            json.dump({
                "offset": offset, "fence": fence,
                "sequencers": {
                    d: s.checkpoint() for d, s in sequencers.items()
                },
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckpt_path(p))

    # ---------------------------------------------------------- sweep

    def sweep_leases(self) -> None:
        """Acquire unowned/expired partitions (bounded by
        max_partitions), renew owned ones, drop deposed ones."""
        for p in list(self.owned):
            if not self.leases.renew(f"p{p}"):
                del self.owned[p]  # deposed
        for p in range(self.n_partitions):
            if p in self.owned:
                continue
            if (self.max_partitions is not None
                    and len(self.owned) >= self.max_partitions):
                break
            fence = self.leases.try_acquire(f"p{p}")
            if fence is None:
                continue
            ck = self._load_checkpoint(p)
            if int(ck.get("fence", 0)) > fence:
                continue  # a newer owner exists; stand down
            topic = SharedFileTopic(
                os.path.join(self.dir, f"submissions-p{p}.jsonl")
            )
            consumer = SharedFileConsumer(topic, int(ck["offset"]))
            seqs = {
                d: DocumentSequencer.restore(s)
                for d, s in ck.get("sequencers", {}).items()
            }
            self.owned[p] = (fence, consumer, seqs)

    # ----------------------------------------------------------- work

    def process_once(self, batch: int = 64) -> int:
        """One pump over every owned partition; returns messages
        processed."""
        done = 0
        for p, (fence, consumer, seqs) in list(self.owned.items()):
            msgs = consumer.poll(batch)
            if not msgs:
                continue
            out = SharedFileTopic(
                os.path.join(self.dir, f"sequenced-p{p}.jsonl")
            )
            stamped = []
            for m in msgs:
                doc = m["docId"]
                seq = seqs.get(doc)
                if seq is None:
                    seq = seqs[doc] = DocumentSequencer(doc)
                if int(m["clientId"]) not in seq.clients:
                    seq.join(int(m["clientId"]))
                res = seq.sequence(
                    int(m["clientId"]),
                    DocumentMessage(
                        client_seq=int(m["clientSeq"]),
                        ref_seq=int(m["refSeq"]),
                        contents=m.get("contents"),
                    ),
                )
                nacked = isinstance(res, NackMessage)
                stamped.append({
                    "docId": doc, "worker": self.worker_id,
                    "seq": None if nacked else res.sequence_number,
                    "msn": None if nacked
                    else res.minimum_sequence_number,
                    "clientSeq": int(m["clientSeq"]),
                    "clientId": int(m["clientId"]),
                    "nack": res.code if nacked else None,
                })
            # Append THEN checkpoint (at-least-once on crash between
            # the two; the test dedups by (doc, clientId, clientSeq) —
            # the same replay-side idempotence Kafka consumers use).
            # One batched append per pump: a per-record append is one
            # lock+fsync EACH (the scalar-pipeline hot-path bug the
            # deli lambdas also had).
            out.append_many(stamped)
            self._save_checkpoint(p, fence, consumer.offset, seqs)
            done += len(msgs)
        return done


def main() -> None:
    args = [a for a in sys.argv[1:]]
    ttl = 2.0
    max_p = None
    if "--ttl" in args:
        i = args.index("--ttl")
        ttl = float(args[i + 1])
        del args[i:i + 2]
    if "--max-partitions" in args:
        i = args.index("--max-partitions")
        max_p = int(args[i + 1])
        del args[i:i + 2]
    shared_dir, worker_id, n_partitions = args[0], args[1], int(args[2])
    w = PartitionWorker(shared_dir, worker_id, n_partitions, ttl, max_p)
    w.sweep_leases()
    print(f"READY {worker_id}", flush=True)
    last_sweep = time.time()
    while True:
        if time.time() - last_sweep > ttl / 3:
            w.sweep_leases()
            last_sweep = time.time()
        if w.process_once() == 0:
            time.sleep(0.02)


if __name__ == "__main__":
    main()
