"""Partitioned sequencer worker: one node of the sharded ordering
fabric (thin wrapper over `server.shard_fabric.ShardWorker`).

Run: python tools/partition_worker_main.py <shared_dir> <worker_id>
        <n_partitions> [--ttl SECONDS] [--max-partitions K]
        [--impl scalar|kernel] [--log-format json|columnar]

Workers coordinate ONLY through the shared directory (the role Kafka +
ZooKeeper play for routerlicious pods): each sweeps the partition
leases toward its fair share (``ceil(N / alive_workers)``), runs one
supervised deli role per owned partition (`rawdeltas-p{k}` →
`deltas-p{k}`, fenced exactly-once recovery via the ``inOff`` scan),
and heartbeats in ``<dir>/workers/``. Kill a worker mid-stream and a
peer's next sweep takes its expired leases over, restores the fenced
checkpoint, and resumes exactly where the dead worker stopped — no
message lost or double-sequenced, and the deposed owner's in-flight
writes are REJECTED at the write path (tests/test_partition_leases.py).

Historical note: before the fabric existed this tool carried its own
one-off worker (scalar `DocumentSequencer` over a bespoke
``submissions-p{k}``/``sequenced-p{k}`` wire with consumer-side
dedup); it now runs the production subsystem — kernel deli and
columnar topics included — via ``--impl`` / ``--log-format``.

Prints "READY <worker_id>" once leases are first swept.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.server.shard_fabric import (  # noqa: E402
    serve_shard_worker,
)


def main() -> None:
    args = [a for a in sys.argv[1:]]

    def _take(flag: str, default=None):
        if flag in args:
            i = args.index(flag)
            val = args[i + 1]
            del args[i:i + 2]
            return val
        return default

    ttl = float(_take("--ttl", "2.0"))
    max_p = _take("--max-partitions")
    impl = _take("--impl") or os.environ.get("FLUID_DELI", "scalar")
    log_format = _take("--log-format")
    if len(args) != 3:
        print(
            "usage: python tools/partition_worker_main.py <shared_dir> "
            "<worker_id> <n_partitions> [--ttl S] [--max-partitions K] "
            "[--impl scalar|kernel] [--log-format json|columnar]",
            file=sys.stderr,
        )
        raise SystemExit(2)
    shared_dir, worker_id, n_partitions = args[0], args[1], int(args[2])
    serve_shard_worker(
        shared_dir, worker_id, n_partitions=n_partitions, ttl_s=ttl,
        max_partitions=int(max_p) if max_p else None, deli_impl=impl,
        log_format=log_format,
    )


if __name__ == "__main__":
    main()
