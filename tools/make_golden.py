"""Record the golden final-state digest for the standard bench stream.

Verification chain (each link independently tested):

1. The scalar Python oracle (core/mergetree.py — slow, obviously
   correct) replays a PREFIX of the stream directly. The oracle is
   O(doc) per op, so a full 1M-op replay is infeasible (hours); the
   prefix grounds the chain in the oracle.
2. The scan engine (ops/mergetree_kernel.py — the lax.scan XLA
   kernel, an implementation independent of the pallas kernel) must
   match the oracle bit-for-bit on that prefix, then replays the FULL
   stream to produce the recorded digest.
3. bench.py requires the pallas engine's full-stream digest to equal
   the recorded scan digest (GOLDEN.json), closing the round-1 gap
   where identity was only gated on a 20k prefix.

The stream is deterministic (seeded); params ride the file and are
checked before the digest is trusted.

Usage: python tools/make_golden.py [n_ops] [oracle_prefix]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.testing.digest import state_digest  # noqa: E402


def main() -> None:
    n_ops = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    n_prefix = int(sys.argv[2]) if len(sys.argv) > 2 else 50_000
    n_clients, seed, initial_len = 1024, 7, 64

    from fluidframework_tpu.core.columnar_replay import ColumnarReplica
    from fluidframework_tpu.core.mergetree import replay_passive
    from fluidframework_tpu.testing.synthetic import generate_stream

    stream = generate_stream(
        n_ops, n_clients=n_clients, seed=seed, initial_len=initial_len
    )

    # 1. oracle on the prefix
    prefix_stream = generate_stream(
        n_prefix, n_clients=n_clients, seed=seed, initial_len=initial_len
    )
    t0 = time.perf_counter()
    oracle = replay_passive(
        prefix_stream.as_messages(),
        initial="".join(map(chr, prefix_stream.text[:initial_len])),
    )
    t_oracle = time.perf_counter() - t0
    oracle_digest = state_digest(oracle.annotated_spans())

    # 2. scan engine: prefix must match the oracle, then the full run
    pre = ColumnarReplica(prefix_stream, initial_len=initial_len, engine="scan")
    pre.replay()
    pre.check_errors()
    if state_digest(pre.annotated_spans()) != oracle_digest:
        print("FATAL: scan engine diverges from oracle on prefix",
              file=sys.stderr)
        sys.exit(1)

    t0 = time.perf_counter()
    full = ColumnarReplica(stream, initial_len=initial_len, engine="scan")
    full.replay()
    full.check_errors()
    t_scan = time.perf_counter() - t0
    digest = state_digest(full.annotated_spans())

    out = {
        "params": {
            "n_ops": n_ops, "n_clients": n_clients, "seed": seed,
            "initial_len": initial_len,
        },
        "digest": digest,
        "chain": {
            "oracle_prefix_ops": n_prefix,
            "oracle_prefix_digest": oracle_digest,
            "oracle_seconds": round(t_oracle, 1),
            "full_engine": "scan",
            "scan_seconds": round(t_scan, 1),
        },
        "final_len": len(full.get_text()),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "GOLDEN.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
