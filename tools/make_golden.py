"""Compute the golden final-state digest for the standard bench stream.

Replays the full benchmark op stream (seed 7, 1024 clients) through
the scalar Python oracle (core/mergetree.py — the slow, obviously-
correct reference implementation) and records a digest of the final
document state (text + annotated spans) in GOLDEN.json. bench.py
verifies the kernel's full-stream final state against this digest,
closing the round-1 gap where bit-identity was only checked on a 20k
prefix (the north star demands the FULL 1M-op replay be bit-identical
— BASELINE.json).

The stream is deterministic (seeded), so a recorded digest is a valid
oracle for exactly these parameters; the parameters are stored
alongside the digest and checked by bench.py before trusting it.

Usage: python tools/make_golden.py [n_ops] (default 1_000_000)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.testing.digest import state_digest  # noqa: E402


def main() -> None:
    n_ops = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    n_clients = 1024
    seed = 7
    initial_len = 64

    from fluidframework_tpu.core.mergetree import replay_passive
    from fluidframework_tpu.testing.synthetic import generate_stream

    stream = generate_stream(
        n_ops, n_clients=n_clients, seed=seed, initial_len=initial_len
    )
    t0 = time.perf_counter()
    oracle = replay_passive(
        stream.as_messages(),
        initial="".join(map(chr, stream.text[:initial_len])),
    )
    dt = time.perf_counter() - t0
    text = oracle.get_text()
    digest = state_digest(oracle.annotated_spans())
    out = {
        "params": {
            "n_ops": n_ops, "n_clients": n_clients, "seed": seed,
            "initial_len": initial_len,
        },
        "final_len": len(text),
        "digest": digest,
        "oracle_seconds": round(dt, 1),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "GOLDEN.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
