"""End-to-end deli pipeline bench CLI (raw topic → stamped deltas).

Runs the live ordering pipeline (the supervised deli datapath over
durable `SharedFileTopic`s) with three sequencer variants on the same
10k-doc x 64-client workload and prints ONE JSON line:

    {"metric": "deli_pipeline_raw_to_deltas", "ops_per_sec": ...,
     "vs_baseline": ..., "vs_scalar_batched": ..., "gate": "bit-identical"}

- `ops_per_sec` / `vs_baseline` — the kernel deli
  (`server.deli_kernel.KernelDeliRole`, vmap'd sequencer kernel, one
  `append_many` per pump) against the SEED scalar pump (per-record
  locked+fsync'd appends, the pre-batching pipeline this PR replaces;
  measured on a bounded prefix — one fsync per record makes full runs
  take hours by design).
- `vs_scalar_batched` — the honest same-batching comparison against
  the scalar deli with the per-pump `append_many` flush.
- `columnar_ops_per_sec` / `columnar_vs_json_log` /
  `columnar_vs_scalar_batched_json` — the same pipeline over the
  COLUMNAR binary op-log (`server.columnar_log` record-batch topics:
  zero per-record JSON decode into the kernel, blob pass-through on
  emit) — the end-to-end numbers where the kernel win survives the
  wire (ROADMAP (a)).

A correctness gate asserts all four (impl x log_format) deltas topics
are bit-identical (stamps, nack codes, MSNs) before reporting.

Observability riders (ISSUE 3): `stage_breakdown` (per-stage wall time
— poll/parse, process+kernel, append, checkpoint), and the checkpoint
cadence comparison `ckpt_cadence` vs `ckpt_every_pump` (time/byte
cadence vs the seed's every-step policy, counters from utils.metrics —
ROADMAP item (b)).

`--shard` switches to the SHARD-SCALING mode
(`testing.deli_bench.run_shard_bench`, bench_configs
`config6_shard_scaling`'s engine): the same workload drained through P
parallel partition pipelines — one OS process per partition
(`server.shard_fabric` slicing) — reporting aggregate ops/s per P and
the P-vs-1 `speedup`, bit-identity gated across partitions. Shard env
knobs: BD_PARTITIONS ("1,4"), BD_IMPL (kernel), BD_LOG_FORMAT
(columnar).

`--devices [1,4,8]` switches to the MULTI-DEVICE scaling mode
(`testing.deli_bench.run_multichip_bench`, bench_configs
`config7_multichip`'s engine): the same [D, B] submission workload is
sequenced by the sharded kernel under each device count (one
subprocess per N so the forced-host-device flag can act; real chips
are used when the host has them), reporting aggregate submissions/s,
per-N `warmup_s`/`forced_host`, `n_devices`, and the peak-vs-base
`speedup` — gated bit-identical across every topology. Env knobs:
BD_DEVICES ("1,4,8"), BD_OPS_PER_DOC (64), BD_REPEATS (3).

Env knobs: BD_DOCS (10000; 2048 in shard mode; 4096 in devices mode),
BD_CLIENTS (64; 8), BD_OPS (ops/client, 1; 2), BD_SEED_RECORDS (400),
BD_BATCH (8192), BD_SCALE (workload shrink).

`--latency` switches to the open-loop LATENCY SLO mode
(`testing.deli_bench.run_latency_bench`, bench_configs
`config9_latency`'s engine): a steady fixed-rate submit load through
the supervised farm, per-op submit→stamp→durable→broadcast spans off
the wire traces, exact + bucket-interpolated p50/p95/p99, doorbells
vs the polling baseline, slowest ops attached from the flight
recorder. Env knobs: BD_RATE_HZ (150), BD_DURATION_S (4).

`--catchup` switches to the SUMMARY CATCH-UP mode
(`testing.deli_bench.run_catchup_bench`, bench_configs
`config10_catchup`'s engine): cold-join latency vs log length with and
without summaries — full-log merge-tree replay vs nearest summary +
op tail (`server.summarizer`), bit-identity gated at every length —
plus broadcast fan-out to hundreds of subscribed readers through the
doorbell-woken read front end.

`--hops` switches to the FUSED-HOP mode
(`testing.deli_bench.run_hop_bench`): the classic
{scriptorium, broadcaster} pair vs the fused durable+broadcast
consumer over one workload — drain ops/s, the hop pair's
fsyncs-per-record, and the `hop_fsync_reduction` headline, with both
topologies' durable+broadcast streams gated bit-identical.

`--latency --fused-hop` adds a THIRD open-loop variant running the
fused durable+broadcast consumer at the same load: the p99 delta of
one fewer wake+fsync in the path (`fused_vs_split_p99`,
`fused_p99_ms` — ROADMAP item-1 follow-up c, config9's MEASURED
section).

`--ingress` switches to the FRONT-DOOR mode
(`testing.deli_bench.run_ingress_bench`, bench_configs
`config12_front_door`'s engine): admission throughput (riddler
tokens + size caps through `server.ingress.IngressRole`) vs bare
routing vs sequencing, plus the overload episode — bounded backlog,
visible throttle nacks, retry-and-converge exactly-once.

`--scenarios` switches to the TRAFFIC-PROFILE SCENARIO mode
(`testing.scenarios.run_scenario_suite`, bench_configs
`config13_scenarios`' engine): the four open-loop scenario primitives
— hot-doc storm, reconnect stampede, 100k-session read swarm,
tenant-skewed mix — each with /slo quantiles, slow-op spans, and a
convergence digest.

`--device-plane [DxM]` switches to the 2-D DEVICE-PLANE mode
(`testing.deli_bench.run_device_plane_bench`, bench_configs
`config15_device_plane`'s engine): ONE ``docs x model`` mesh serving
sequencing AND summary folds — the sequencer's verdict digests gated
bit-identical between single-device and the plane's docs-axis slice,
and the summarizer's kernel-vs-overlay fold backends gated
byte-identical at every emission with `fold_backend_speedup` reported
where honestly measurable.

Usage: python tools/bench_deli.py
    [--shard | --devices [LIST] | --device-plane [DxM]
     | --latency [--fused-hop]
     | --catchup | --hops | --ingress | --scenarios]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)

if "--shard" in sys.argv:
    os.environ["BD_SHARD"] = "1"

if "--hops" in sys.argv:
    # Fused-hop mode: classic {scriptorium, broadcaster} pair vs the
    # fused durable+broadcast consumer
    # (supervisor.ScriptoriumBroadcasterRole) over one workload —
    # drain ops/s per topology, the hop pair's fsyncs-per-record
    # (topic_fsyncs_total off the children's heartbeat metrics), and
    # the split/fused `hop_fsync_reduction` headline; both topologies'
    # durable+broadcast streams gated bit-identical. Env knobs:
    # BD_DOCS (64), BD_CLIENTS (8), BD_OPS (4), BD_LOG_FORMAT
    # (columnar), BD_IMPL (kernel).
    os.environ["BD_HOPS"] = "1"

if "--catchup" in sys.argv:
    # Summary catch-up mode: cold-join latency vs log length with and
    # without summaries (full-log merge-tree replay vs nearest summary
    # + op tail, bit-identity gated at every length) plus broadcast
    # fan-out to BD_SUBSCRIBERS readers through the doorbell-woken
    # read front end (bench_configs config10_catchup's engine). Env
    # knobs: BD_LOG_LENGTHS ("10000,30000,100000"), BD_SUMMARY_OPS
    # (2000), BD_SUBSCRIBERS (200), BD_LOG_FORMAT (json).
    os.environ["BD_CATCHUP"] = "1"

if "--scenarios" in sys.argv:
    # Traffic-profile scenario mode: the four open-loop scenario
    # primitives (testing.scenarios.run_scenario_suite — hot-doc
    # storm, reconnect stampede, 100k-session read swarm, tenant-
    # skewed mix), each with /slo quantiles, slow-op spans and a
    # convergence digest (bench_configs config13_scenarios' engine).
    # Env knobs: BD_SCALE (suite scale), BD_IMPL (scalar), BD_SESSIONS
    # (100000 swarm sessions), BD_LOG_FORMAT (json).
    os.environ["BD_SCENARIOS"] = "1"

if "--latency" in sys.argv:
    # Open-loop latency SLO mode: p50/p99 submit→broadcast through
    # the supervised farm at a steady fixed rate, doorbells ON vs the
    # polling baseline (bench_configs config9_latency's engine). Env
    # knobs: BD_RATE_HZ (150), BD_DURATION_S (4), BD_DOCS (2),
    # BD_CLIENTS (2). See testing.deli_bench.run_latency_bench.
    os.environ["BD_LATENCY"] = "1"
    if "--fused-hop" in sys.argv:
        os.environ["BD_FUSED_HOP"] = "1"

if "--ingress" in sys.argv:
    # Front-door mode: admission throughput + the overload episode
    # (bench_configs config12_front_door's engine). Env knobs:
    # BD_DOCS (2000), BD_CLIENTS (16), BD_OPS (2), BD_LOG_FORMAT
    # (json), BD_PARTITIONS (2).
    os.environ["BD_INGRESS"] = "1"

if "--device-plane" in sys.argv:
    # 2-D device-plane mode: ONE docs x model mesh serving sequencing
    # AND summary folds (testing.deli_bench.run_device_plane_bench,
    # bench_configs config15_device_plane's engine) — sequencer
    # digests gated 1-dev vs plane slice, summarizer fold backends
    # (vmapped kernel vs overlay-pallas) gated byte-identical at
    # every emission, fold_backend_speedup reported where honestly
    # measurable (fold_parity_skip_reason otherwise). Env knobs:
    # BD_DOCS (2048), BD_OPS_PER_DOC (64), BD_FOLD_DOCS (4),
    # BD_FOLD_OPS (1500), BD_REPEATS (3).
    i = sys.argv.index("--device-plane")
    arg = sys.argv[i + 1] if len(sys.argv) > i + 1 else ""
    os.environ["BD_DEVICE_PLANE"] = (
        arg if arg and not arg.startswith("-") else "2x2"
    )

if "--devices" in sys.argv:
    # Multi-device scaling mode: `--devices [1,4,8]` measures the
    # SHARDED sequencer kernel's aggregate ops/s per device count
    # (one subprocess per N — real chips when present, forced virtual
    # host CPU devices otherwise), bit-identity gated across
    # topologies, reporting per-N warmup_s and the peak-vs-base
    # speedup. See testing.deli_bench.run_multichip_bench.
    i = sys.argv.index("--devices")
    arg = sys.argv[i + 1] if len(sys.argv) > i + 1 else ""
    os.environ["BD_DEVICES"] = (
        arg if arg and not arg.startswith("-") else "1,4,8"
    )

from fluidframework_tpu.testing.deli_bench import main  # noqa: E402

if __name__ == "__main__":
    main()
