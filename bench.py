"""Headline benchmark: merge-tree sequenced-op replay throughput.

Replays the LAGGED synthetic SharedString op stream (insert/remove/
annotate from 1024 round-robin clients whose refSeqs trail the head by
up to the collaboration window — real concurrent-perspective
resolution on every lagged op, the honest BASELINE.md config-2 shape)
through the OVERLAY pallas TPU engine (ops/overlay_pallas.py via
core/overlay_replay.py: fused per-op kernel, per-op work scales with
the collab window, settled content folds out to an HBM log), and
through the scalar Python oracle as the baseline, then prints ONE
JSON line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

`vs_baseline` is kernel throughput / scalar-oracle throughput on the
same workload. A correctness gate first replays a prefix through both
paths and asserts identical final text, and the FULL-stream final
state is gated against GOLDEN.json (the bit-identity contract,
BASELINE.json north_star; recorded by tools/lagged_golden.py from the
native C++ engine with all staged digests, oracle-grounded prefix).

The jax persistent compilation cache does not engage on this
backend (platform "axon" is outside jax's supported-cache list), so
every process pays the Mosaic compile. The bench uses ONE fixed
window/chunk geometry: the warm-up compiles everything the timed run
needs, and the timed region never compiles, grows, or waits on
uploads (the op stream is drained to the device before t0).

Env knobs: BENCH_OPS (default 1_000_000), BENCH_GATE_OPS (20_000),
BENCH_ORACLE_OPS (20_000), BENCH_CLIENTS (1024), BENCH_CHUNK (256),
BENCH_WINDOW (2048 overlay) / BENCH_CAPACITY (131072 row-model),
BENCH_REMOVERS (24), BENCH_LAGGED (1), BENCH_SYNC (4),
BENCH_ENGINE (auto | overlay | pallas | scan).
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)

def main() -> None:
    n_ops = int(os.environ.get("BENCH_OPS", 1_000_000))
    n_gate = min(int(os.environ.get("BENCH_GATE_OPS", 20_000)), n_ops)
    n_oracle = min(int(os.environ.get("BENCH_ORACLE_OPS", 20_000)), n_ops)
    n_clients = int(os.environ.get("BENCH_CLIENTS", 1024))
    chunk = int(os.environ.get("BENCH_CHUNK", 256))
    capacity = int(os.environ.get("BENCH_CAPACITY", 131072))
    window = int(os.environ.get("BENCH_WINDOW", 2048))
    n_removers = int(os.environ.get("BENCH_REMOVERS", 24))
    lagged = os.environ.get("BENCH_LAGGED", "1") != "0"
    collab_window = 1024
    sync = int(os.environ.get("BENCH_SYNC", 4))
    engine = os.environ.get("BENCH_ENGINE", "auto")
    initial_len = 64

    import jax

    from fluidframework_tpu.core.columnar_replay import ColumnarReplica
    from fluidframework_tpu.core.mergetree import replay_passive
    from fluidframework_tpu.core.overlay_replay import OverlayDeviceReplica
    from fluidframework_tpu.testing.synthetic import (
        generate_lagged_stream,
        generate_stream,
    )

    if engine == "auto":
        engine = (
            "overlay"
            if jax.default_backend() in ("tpu", "axon")
            else "scan"
        )

    def gen(n):
        if lagged:
            return generate_lagged_stream(
                n, n_clients=n_clients, seed=7, window=collab_window,
                initial_len=initial_len,
                cache_dir=os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    ".stream_cache",
                ),
            )
        return generate_stream(
            n, n_clients=n_clients, seed=7, initial_len=initial_len
        )

    def make_replica(stream):
        if engine == "overlay":
            return OverlayDeviceReplica(
                stream, initial_len=initial_len, chunk_size=chunk,
                window=window, n_removers=n_removers,
            )
        return ColumnarReplica(
            stream, initial_len=initial_len, chunk_size=chunk,
            capacity=capacity, sync_interval=sync, engine=engine,
            n_removers=n_removers,
        )

    # Row-model engines keep every live row in the kernel table; fail
    # fast if the fixed capacity cannot hold the stream (the overlay
    # engine has no such cliff: settled content folds out of the
    # table, so only the collab window must fit — ERR_CAPACITY flags
    # loudly if it doesn't).
    est_rows = int(n_ops * 0.10) + 2 * chunk * sync + 64
    if engine != "overlay" and est_rows > capacity:
        print(
            f"FATAL: BENCH_CAPACITY={capacity} too small for "
            f"BENCH_OPS={n_ops} (est. {est_rows} live rows); raise "
            "BENCH_CAPACITY (multiple of 1024; VMEM caps it at 131072) "
            "or use BENCH_ENGINE=overlay.",
            file=sys.stderr,
        )
        sys.exit(1)

    print(
        f"generating {n_ops} {'lagged ' if lagged else ''}ops from "
        f"{n_clients} clients...",
        file=sys.stderr,
    )
    stream = gen(n_ops)

    # ---- correctness gate: kernel vs scalar oracle on a prefix --------
    gate_stream = gen(n_gate)
    gate = make_replica(gate_stream)
    if engine == "overlay":
        # Incremental per-chunk path (the fused executable is shape-
        # specialized to the main stream; the gate doesn't need it).
        gate.replay(limit_chunks=gate.n_chunks)
    else:
        gate.replay()
    gate.check_errors()
    oracle = replay_passive(
        gate_stream.as_messages(), initial="".join(map(chr, gate_stream.text[:initial_len]))
    )
    if gate.get_text() != oracle.get_text():
        print("FATAL: kernel/oracle divergence on gate prefix", file=sys.stderr)
        sys.exit(1)
    print(f"gate ok ({n_gate} ops bit-identical)", file=sys.stderr)

    # ---- scalar oracle baseline --------------------------------------
    t0 = time.perf_counter()
    oracle_msgs = list(gate_stream.as_messages(n_oracle))
    t_decode = time.perf_counter() - t0
    t0 = time.perf_counter()
    replay_passive(
        oracle_msgs, initial="".join(map(chr, gate_stream.text[:initial_len]))
    )
    t_oracle = time.perf_counter() - t0
    n_oracle = len(oracle_msgs)  # as_messages caps at the gate stream length
    oracle_ops_s = n_oracle / t_oracle
    print(
        f"scalar oracle: {oracle_ops_s:,.0f} ops/s "
        f"({n_oracle} ops in {t_oracle:.2f}s; decode {t_decode:.2f}s)",
        file=sys.stderr,
    )

    # ---- warm-up: compile the replay executable at the run's exact
    # shapes. The overlay engine replays the WHOLE stream as one fused
    # device dispatch, so warming = running the full fused replay once
    # (compile + ~1s execute); the timed run below is then a pure
    # cache hit on identical shapes.
    t0 = time.perf_counter()
    w = make_replica(stream)
    if engine == "overlay":
        w.replay()
    else:
        w.replay(limit_chunks=2)
    print(f"warm-up done in {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # ---- kernel replay (timed) ---------------------------------------
    # The stream upload is the load phase (the reference replay tool
    # pre-parses op files before its timed loop); replay is timed from
    # device-resident ops.
    repeats = int(os.environ.get("BENCH_REPEATS", 3))
    times = []
    replica = None
    for _ in range(max(repeats, 1)):
        replica = make_replica(stream)
        if engine == "overlay":
            # Drain the stream upload before the timed region: an
            # in-flight async transfer queues the replay dispatch
            # behind it and pollutes the measurement (the round-3
            # run-to-run variance).
            replica.prepare()
            jax.block_until_ready(replica._dev)
            jax.block_until_ready(replica.log)
        t0 = time.perf_counter()
        replica.replay()
        # A value FETCH (not block_until_ready) closes the timing
        # region: on the tunneled backend, block_until_ready can
        # return before the device finishes; a fetch of
        # loop-dependent state cannot.
        replica.check_errors()
        times.append(time.perf_counter() - t0)
    t_kernel = sum(times) / len(times)
    stddev = (
        sum((t - t_kernel) ** 2 for t in times) / len(times)
    ) ** 0.5
    print(
        f"runs: {[round(t, 3) for t in times]} mean {t_kernel:.3f}s "
        f"stddev {stddev:.3f}s", file=sys.stderr,
    )
    kernel_ops_s = n_ops / t_kernel
    if engine == "overlay":
        detail = (
            f"window {replica.window}, residual rows "
            f"{int(replica.table.n_rows)}, settled len "
            f"{int(replica.table.settled_len)}, fold records "
            f"{int(replica.cursor)}"
        )
    else:
        detail = (
            f"{replica.compactions} compactions, capacity "
            f"{replica.capacity}, rows {int(replica.table.n_rows)}, "
            f"final len "
            f"{int(sum(replica.table.length[: int(replica.table.n_rows)]))}"
        )
    print(
        f"kernel ({engine}): {kernel_ops_s:,.0f} ops/s "
        f"({n_ops} ops in {t_kernel:.2f}s, {detail})",
        file=sys.stderr,
    )

    # ---- FULL-stream bit-identity vs the recorded oracle digest ------
    # (tools/make_golden.py replays the same deterministic stream
    # through the scalar Python oracle and records the canonical
    # final-state digest; this closes the round-1 gap where identity
    # was only gated on a 20k prefix.)
    golden_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "GOLDEN.json"
    )
    if os.path.exists(golden_path):
        with open(golden_path) as f:
            golden = json.load(f)
        params = {
            "n_ops": n_ops, "n_clients": n_clients, "seed": 7,
            "initial_len": initial_len,
        }
        if lagged:
            params.update({"lagged": True, "window": collab_window})
        if golden.get("params") == params:
            from fluidframework_tpu.testing.digest import state_digest

            producer = golden.get("chain", {}).get("full_engine", "?")
            d = state_digest(replica.annotated_spans())
            if d != golden["digest"]:
                print(
                    "FATAL: full-stream final state diverges from the "
                    f"recorded {producer}-produced digest", file=sys.stderr,
                )
                sys.exit(1)
            print(
                f"full {n_ops}-op final state bit-identical to the "
                f"{producer}-produced digest (GOLDEN.json)",
                file=sys.stderr,
            )
        else:
            print(
                "GOLDEN.json params mismatch; full-stream identity not "
                "checked", file=sys.stderr,
            )

    print(
        json.dumps(
            {
                "metric": "mergetree_replay_ops_per_sec_1024clients",
                "value": round(kernel_ops_s, 1),
                "unit": "ops/s",
                "vs_baseline": round(kernel_ops_s / oracle_ops_s, 3),
            }
        )
    )


def _main_with_retry() -> None:
    """The tunneled TPU's remote compile helper occasionally 500s
    (transient terminal-side env flake, observed repeatedly); a fresh
    process retry succeeds. Retry the whole bench up to twice in a
    subprocess so one infra hiccup doesn't record a failed round."""
    attempt = int(os.environ.get("BENCH_ATTEMPT", "0"))
    try:
        main()
        return
    except SystemExit:
        raise
    except Exception as exc:
        # Only the remote-compile-helper hiccup is transient; other
        # INTERNAL errors are deterministic and must surface.
        if "remote_compile" not in str(exc) or attempt >= 2:
            raise
        print(
            f"transient TPU compile failure (attempt {attempt}); "
            "retrying in a fresh process...", file=sys.stderr,
        )
    # Replace this process outright: the dying parent must not hold
    # the TPU client while the retry initializes its own.
    os.environ["BENCH_ATTEMPT"] = str(attempt + 1)
    os.execv(sys.executable, [sys.executable, os.path.abspath(__file__)])


if __name__ == "__main__":
    _main_with_retry()
