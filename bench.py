"""Headline benchmark: merge-tree sequenced-op replay throughput.

Replays a synthetic mixed SharedString op stream (insert/remove/
annotate from 1024 round-robin clients — BASELINE.md config 2 shape)
through the vectorized TPU kernel via the columnar replay engine, and
through the scalar Python oracle as the baseline, then prints ONE JSON
line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

`vs_baseline` is kernel throughput / scalar-oracle throughput on the
same workload. A correctness gate first replays a prefix through both
paths and asserts identical final text (the project's bit-identity
contract, BASELINE.json north_star).

Env knobs: BENCH_OPS (default 1_000_000), BENCH_GATE_OPS (default
20_000), BENCH_ORACLE_OPS (default 20_000), BENCH_CLIENTS (1024).
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    n_ops = int(os.environ.get("BENCH_OPS", 1_000_000))
    n_gate = min(int(os.environ.get("BENCH_GATE_OPS", 20_000)), n_ops)
    n_oracle = min(int(os.environ.get("BENCH_ORACLE_OPS", 20_000)), n_ops)
    n_clients = int(os.environ.get("BENCH_CLIENTS", 1024))
    initial_len = 64

    from fluidframework_tpu.core.columnar_replay import ColumnarReplica
    from fluidframework_tpu.core.mergetree import replay_passive
    from fluidframework_tpu.testing.synthetic import generate_stream

    print(f"generating {n_ops} ops from {n_clients} clients...", file=sys.stderr)
    stream = generate_stream(
        n_ops, n_clients=n_clients, seed=7, initial_len=initial_len
    )

    # ---- correctness gate: kernel vs scalar oracle on a prefix --------
    gate_stream = generate_stream(
        n_gate, n_clients=n_clients, seed=7, initial_len=initial_len
    )
    gate = ColumnarReplica(gate_stream, initial_len=initial_len)
    gate.replay()
    gate.check_errors()
    oracle = replay_passive(
        gate_stream.as_messages(), initial="".join(map(chr, gate_stream.text[:initial_len]))
    )
    if gate.get_text() != oracle.get_text():
        print("FATAL: kernel/oracle divergence on gate prefix", file=sys.stderr)
        sys.exit(1)
    print(f"gate ok ({n_gate} ops bit-identical)", file=sys.stderr)

    # ---- scalar oracle baseline --------------------------------------
    t0 = time.perf_counter()
    oracle_msgs = list(gate_stream.as_messages(n_oracle))
    t_decode = time.perf_counter() - t0
    t0 = time.perf_counter()
    replay_passive(
        oracle_msgs, initial="".join(map(chr, gate_stream.text[:initial_len]))
    )
    t_oracle = time.perf_counter() - t0
    n_oracle = len(oracle_msgs)  # as_messages caps at the gate stream length
    oracle_ops_s = n_oracle / t_oracle
    print(
        f"scalar oracle: {oracle_ops_s:,.0f} ops/s "
        f"({n_oracle} ops in {t_oracle:.2f}s; decode {t_decode:.2f}s)",
        file=sys.stderr,
    )

    # ---- kernel replay (warm once, then timed) -----------------------
    warm = ColumnarReplica(
        generate_stream(2048, n_clients=n_clients, seed=3, initial_len=initial_len),
        initial_len=initial_len,
    )
    warm.replay()  # compile cache warm-up

    replica = ColumnarReplica(stream, initial_len=initial_len)
    t0 = time.perf_counter()
    replica.replay()
    replica.table.n_rows.block_until_ready()
    t_kernel = time.perf_counter() - t0
    replica.check_errors()
    kernel_ops_s = n_ops / t_kernel
    print(
        f"kernel: {kernel_ops_s:,.0f} ops/s ({n_ops} ops in {t_kernel:.2f}s, "
        f"{replica.compactions} compactions, final len "
        f"{int(sum(replica.table.length[: int(replica.table.n_rows)]))})",
        file=sys.stderr,
    )

    print(
        json.dumps(
            {
                "metric": "mergetree_replay_ops_per_sec_1024clients",
                "value": round(kernel_ops_s, 1),
                "unit": "ops/s",
                "vs_baseline": round(kernel_ops_s / oracle_ops_s, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
