"""Headline benchmark: merge-tree sequenced-op replay throughput.

Replays a synthetic mixed SharedString op stream (insert/remove/
annotate from 1024 round-robin clients — BASELINE.md config 2 shape)
through the pallas TPU replay engine (ops/mergetree_pallas.py +
device-side compaction, ops/zamboni.py) via core/columnar_replay.py,
and through the scalar Python oracle as the baseline, then prints ONE
JSON line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

`vs_baseline` is kernel throughput / scalar-oracle throughput on the
same workload. A correctness gate first replays a prefix through both
paths and asserts identical final text (the project's bit-identity
contract, BASELINE.json north_star).

The jax persistent compilation cache does not engage on this
backend (platform "axon" is outside jax's supported-cache list), so
every process pays the Mosaic compile (~3-4 min for the chunk
kernel). The bench therefore uses ONE fixed table capacity sized for
the whole run — the gate replay compiles everything the timed run
needs, and the timed region never compiles or grows.

Env knobs: BENCH_OPS (default 1_000_000), BENCH_GATE_OPS (20_000),
BENCH_ORACLE_OPS (20_000), BENCH_CLIENTS (1024), BENCH_CHUNK (2048),
BENCH_CAPACITY (131072 fixed), BENCH_SYNC (4), BENCH_ENGINE (auto).
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)

# 131072 rows (~10MB of VMEM tiles) holds the 1M-op stream's live row
# count (~90k at the end) with the sync-window margin; 2x that exceeds
# the core's VMEM and Mosaic refuses the kernel.


def main() -> None:
    n_ops = int(os.environ.get("BENCH_OPS", 1_000_000))
    n_gate = min(int(os.environ.get("BENCH_GATE_OPS", 20_000)), n_ops)
    n_oracle = min(int(os.environ.get("BENCH_ORACLE_OPS", 20_000)), n_ops)
    n_clients = int(os.environ.get("BENCH_CLIENTS", 1024))
    chunk = int(os.environ.get("BENCH_CHUNK", 2048))
    capacity = int(os.environ.get("BENCH_CAPACITY", 131072))
    sync = int(os.environ.get("BENCH_SYNC", 4))
    engine = os.environ.get("BENCH_ENGINE", "auto")
    initial_len = 64

    from fluidframework_tpu.core.columnar_replay import ColumnarReplica
    from fluidframework_tpu.core.mergetree import replay_passive
    from fluidframework_tpu.testing.synthetic import generate_stream

    def make_replica(stream, cap=capacity):
        return ColumnarReplica(
            stream, initial_len=initial_len, chunk_size=chunk,
            capacity=cap, sync_interval=sync, engine=engine,
        )

    # Fail fast if the fixed capacity cannot hold the stream: live
    # rows grow ~0.091/op on this mix (measured: 91,172 rows after the
    # 1M-op replay); growth inside the timed region would recompile
    # (minutes) or exceed VMEM.
    est_rows = int(n_ops * 0.10) + 2 * chunk * sync + 64
    if est_rows > capacity:
        print(
            f"FATAL: BENCH_CAPACITY={capacity} too small for "
            f"BENCH_OPS={n_ops} (est. {est_rows} live rows); raise "
            "BENCH_CAPACITY (multiple of 1024; VMEM caps it at 131072).",
            file=sys.stderr,
        )
        sys.exit(1)

    print(f"generating {n_ops} ops from {n_clients} clients...", file=sys.stderr)
    stream = generate_stream(
        n_ops, n_clients=n_clients, seed=7, initial_len=initial_len
    )

    # ---- correctness gate: kernel vs scalar oracle on a prefix --------
    gate_stream = generate_stream(
        n_gate, n_clients=n_clients, seed=7, initial_len=initial_len
    )
    gate = make_replica(gate_stream)
    gate.replay()
    gate.check_errors()
    oracle = replay_passive(
        gate_stream.as_messages(), initial="".join(map(chr, gate_stream.text[:initial_len]))
    )
    if gate.get_text() != oracle.get_text():
        print("FATAL: kernel/oracle divergence on gate prefix", file=sys.stderr)
        sys.exit(1)
    print(f"gate ok ({n_gate} ops bit-identical)", file=sys.stderr)

    # ---- scalar oracle baseline --------------------------------------
    t0 = time.perf_counter()
    oracle_msgs = list(gate_stream.as_messages(n_oracle))
    t_decode = time.perf_counter() - t0
    t0 = time.perf_counter()
    replay_passive(
        oracle_msgs, initial="".join(map(chr, gate_stream.text[:initial_len]))
    )
    t_oracle = time.perf_counter() - t0
    n_oracle = len(oracle_msgs)  # as_messages caps at the gate stream length
    oracle_ops_s = n_oracle / t_oracle
    print(
        f"scalar oracle: {oracle_ops_s:,.0f} ops/s "
        f"({n_oracle} ops in {t_oracle:.2f}s; decode {t_decode:.2f}s)",
        file=sys.stderr,
    )

    # ---- warm-up: compile the chunk kernel + compaction at the run's
    # exact shapes (the gate used the same capacity, but the main
    # stream's arena/segment shapes differ; two chunks suffice).
    t0 = time.perf_counter()
    w = make_replica(stream)
    w.replay(limit_chunks=2)
    print(f"warm-up done in {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # ---- kernel replay (timed) ---------------------------------------
    replica = make_replica(stream)
    t0 = time.perf_counter()
    replica.replay()
    replica.table.n_rows.block_until_ready()
    t_kernel = time.perf_counter() - t0
    replica.check_errors()
    kernel_ops_s = n_ops / t_kernel
    print(
        f"kernel ({replica.engine}): {kernel_ops_s:,.0f} ops/s "
        f"({n_ops} ops in {t_kernel:.2f}s, "
        f"{replica.compactions} compactions, capacity {replica.capacity}, "
        f"rows {int(replica.table.n_rows)}, final len "
        f"{int(sum(replica.table.length[: int(replica.table.n_rows)]))})",
        file=sys.stderr,
    )

    # ---- FULL-stream bit-identity vs the recorded oracle digest ------
    # (tools/make_golden.py replays the same deterministic stream
    # through the scalar Python oracle and records the canonical
    # final-state digest; this closes the round-1 gap where identity
    # was only gated on a 20k prefix.)
    golden_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "GOLDEN.json"
    )
    if os.path.exists(golden_path):
        with open(golden_path) as f:
            golden = json.load(f)
        params = {
            "n_ops": n_ops, "n_clients": n_clients, "seed": 7,
            "initial_len": initial_len,
        }
        if golden.get("params") == params:
            from fluidframework_tpu.testing.digest import state_digest

            d = state_digest(replica.annotated_spans())
            if d != golden["digest"]:
                print(
                    "FATAL: full-stream final state diverges from the "
                    "oracle digest", file=sys.stderr,
                )
                sys.exit(1)
            print(
                f"full {n_ops}-op final state bit-identical to oracle "
                "digest (GOLDEN.json)", file=sys.stderr,
            )
        else:
            print(
                "GOLDEN.json params mismatch; full-stream identity not "
                "checked", file=sys.stderr,
            )

    print(
        json.dumps(
            {
                "metric": "mergetree_replay_ops_per_sec_1024clients",
                "value": round(kernel_ops_s, 1),
                "unit": "ops/s",
                "vs_baseline": round(kernel_ops_s / oracle_ops_s, 3),
            }
        )
    )


def _main_with_retry() -> None:
    """The tunneled TPU's remote compile helper occasionally 500s
    (transient terminal-side env flake, observed repeatedly); a fresh
    process retry succeeds. Retry the whole bench up to twice in a
    subprocess so one infra hiccup doesn't record a failed round."""
    attempt = int(os.environ.get("BENCH_ATTEMPT", "0"))
    try:
        main()
        return
    except SystemExit:
        raise
    except Exception as exc:
        # Only the remote-compile-helper hiccup is transient; other
        # INTERNAL errors are deterministic and must surface.
        if "remote_compile" not in str(exc) or attempt >= 2:
            raise
        print(
            f"transient TPU compile failure (attempt {attempt}); "
            "retrying in a fresh process...", file=sys.stderr,
        )
    # Replace this process outright: the dying parent must not hold
    # the TPU client while the retry initializes its own.
    os.environ["BENCH_ATTEMPT"] = str(attempt + 1)
    os.execv(sys.executable, [sys.executable, os.path.abspath(__file__)])


if __name__ == "__main__":
    _main_with_retry()
